"""The TPU session templates must not drift from the library APIs.

Round 4 caught the ``levels`` item crashing on an API change that every
unit test missed — the templates are format-strings executed only when
the tunnel finally answers, which is exactly when a crash is most
expensive. This module (a) parse-checks every item template and (b)
EXECUTES the two most API-coupled items end-to-end at shrunken sizes in
bounded subprocesses on the CPU platform, asserting a clean RESULT
record."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _session_module():
    spec = importlib.util.spec_from_file_location(
        "tpu_session", os.path.join(REPO, "scripts", "tpu_session.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _shrink(code: str) -> str:
    code = code.replace("n = 100_000", "n = 3_000")
    code = code.replace("n2 = 100_000", "n2 = 3_000")
    code = code.replace("repeats=8", "repeats=2")
    code = code.replace("repeats=6", "repeats=2")
    code = code.replace("repeats=5", "repeats=2")
    code = code.replace("repeats=3", "repeats=2")
    code = code.replace(
        "for b in (32, 128, 256, 1024, 2048, 4096):", "for b in (4, 8):"
    )
    code = code.replace("for b in (32, 256):", "for b in (4,):")
    code = code.replace(
        "rmat_graph(18, edge_factor=8, seed=1)",
        "rmat_graph(10, edge_factor=4, seed=1)",
    )
    code = code.replace("140_000, 140_000", "4_000, 4_000")
    code = code.replace("for trips in (4, 64):", "for trips in (2, 6):")
    code = code.replace("(walls[64] - walls[4]) / 60.0",
                        "(walls[6] - walls[2]) / 4.0")
    code = code.replace("wall_T4_s=walls[4], wall_T64_s=walls[64]",
                        "wall_T4_s=walls[2], wall_T64_s=walls[6]")
    code = code.replace("dispatch_s=walls[4] - 4 * per_level",
                        "dispatch_s=walls[2] - 2 * per_level")
    return code


def test_all_templates_parse_and_format():
    import ast

    m = _session_module()
    for name, (code, _timeout) in m.ITEMS.items():
        if code is None:  # driver-function item (batch_rmat)
            continue
        ast.parse(code.format(repo=REPO))
    # the per-leg rmat templates parse with representative arguments
    ast.parse(m.RMAT_PREP_SUB.format(
        repo=REPO, cache="/tmp/c.npz", scale=18, ef=8, seed=1,
        sizes=(32, 256)))
    ast.parse(m.RMAT_NATIVE_SUB.format(
        repo=REPO, cache="/tmp/c.npz", sizes=(32, 256)))
    ast.parse(m.RMAT_DEV_LEG_SUB.format(
        repo=REPO, cache="/tmp/c.npz", b=32, mode="sync", key="sync/32"))


def _run_item(name: str, required_keys: tuple) -> dict:
    m = _session_module()
    code = _shrink(m.ITEMS[name][0].format(repo=REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=500, env=env,
    )
    results = [
        line for line in r.stdout.splitlines() if line.startswith("RESULT ")
    ]
    assert results, f"{name}: no RESULT line:\n{(r.stdout + r.stderr)[-1500:]}"
    rec = json.loads(results[-1][len("RESULT "):])
    for k in required_keys:
        assert k in rec, (name, k, rec)
    return rec


@pytest.mark.slow
def test_pallas_item_executes():
    rec = _run_item(
        "pallas",
        ("compiles", "compiles_at_bench_geom", "fused_compiles",
         "resolved_modes", "pallas_hops_ok"),
    )
    assert rec["pallas_hops_ok"] and rec.get("fused_hops_ok", True)


@pytest.mark.slow
def test_levels_item_executes():
    rec = _run_item("levels", ("pallas_compiles", "xla", "fused_compiles"))
    assert "device_level_s" in rec["xla"]
    if rec["fused_compiles"]:
        assert "device_level_s" in rec["fused"]


@pytest.mark.slow
def test_batch_items_execute():
    # batch and batch_rmat are separate items (a device-level failure
    # wedges the process's TPU context, so they must not share one — the
    # 2026-07-31 on-chip run lost the RMAT leg to the b=2048 wedge).
    rec = _run_item("batch", ("batch_100k",))
    for row in rec["batch_100k"].values():
        assert "per_query_us" in row, rec


@pytest.mark.slow
def test_batch_rmat_driver_executes_and_resumes(tmp_path):
    """The resumable per-leg rmat driver (round-4's 900 s monolith burned
    a whole hardware window): every leg runs end-to-end at RMAT-10 on
    CPU, rows land with measurements, the record is honestly flagged
    incomplete (CPU legs never count as device evidence), and a second
    call banks nothing twice — pre-seeded non-cpu legs are skipped and
    produce a clean record."""
    m = _session_module()
    partial = str(tmp_path / "rmat_partial.json")
    rec = m.run_batch_rmat(scale=10, ef=4, seed=1, sizes=(4,),
                           partial_path=partial, leg_timeout=500)
    rows = rec["batch_rmat18"]
    for key in ("native/4", "sync/4", "minor/4"):
        assert "per_query_us" in rows[key], rec
    # on the CPU platform the device legs must NOT be banked as done
    assert "error" in rec and "incomplete" in rec["error"], rec
    assert rec["platform"] == "cpu"

    # resume: bank fake on-chip legs, and only the missing work reruns;
    # native rows are already banked, so the second call is device-free
    import json as _json

    banked = dict(rows)
    for key in ("sync/4", "minor/4"):
        banked[key] = dict(banked[key], platform="tpu")
    with open(partial, "w") as f:
        _json.dump({"rows": banked}, f)
    rec2 = m.run_batch_rmat(scale=10, ef=4, seed=1, sizes=(4,),
                            partial_path=partial, leg_timeout=500)
    assert "error" not in rec2, rec2
    assert rec2["platform"] == "tpu"
    assert rec2["elapsed_s"] < 60, "banked legs must not re-run"
    assert not os.path.exists(partial), "complete sweep clears partial"


@pytest.mark.slow
def test_deepcap_item_executes():
    rec = _run_item("deepcap", ("capped_queries", "parity_bad",
                                "auto_parity_bad"))
    assert "error" not in rec, rec
    assert rec["capped_queries"] >= 16, rec
    assert rec["parity_bad"] == 0 and rec["auto_parity_bad"] == 0, rec


@pytest.mark.slow
def test_profile_item_executes():
    artifact = os.path.join(REPO, "PROFILE_FUSED.json")
    before = os.path.getmtime(artifact) if os.path.exists(artifact) else None
    rec = _run_item("profile", ("hops_ok", "median_solve_s"))
    assert "error" not in rec, rec
    assert rec["hops_ok"], rec
    assert rec.get("per_process_us") and rec.get("top_ops_us"), rec
    # the committed artifact is chip-only: this CPU-forced smoke must
    # leave it untouched (assert the NON-WRITE, not just the platform)
    after = os.path.getmtime(artifact) if os.path.exists(artifact) else None
    assert before == after, "CPU smoke clobbered PROFILE_FUSED.json"
    assert rec["platform"] == "cpu"


@pytest.mark.slow
def test_unroll_item_executes():
    rec = _run_item("unroll", ("unroll_100k", "unroll_sharded1"))
    assert "error" not in rec, rec
    for key, row in rec["unroll_100k"].items():
        assert row.get("hops_ok"), (key, rec)
        assert "ms_per_level" in row, (key, rec)
    for key, row in rec["unroll_sharded1"].items():
        assert row.get("hops_ok") and "ms_per_level" in row, (key, rec)


@pytest.mark.slow
def test_batch_minor_item_executes():
    rec = _run_item("batch_minor", ("parity_ok", "minor_100k",
                                    "minor8_100k", "sync_control_256"))
    assert rec["parity_ok"], rec
    assert "error" not in rec, rec
    for key in ("minor_100k", "minor8_100k"):
        for row in rec[key].values():
            assert "per_query_us" in row, rec
