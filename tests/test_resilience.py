"""The resilience layer (bibfs_tpu/serve/resilience) and its
integration through the synchronous engine: error taxonomy, retry
backoff, circuit-breaker lifecycle, the fallback ladder
device -> host-native -> serial, poison-batch bisection, partial-
failure query_many, and the health state machine.

Correctness bar: every query that a fault does NOT unrecoverably
poison must still resolve oracle-correct THROUGH the failures — the
fallback ladder may trade throughput for availability, never answers.
"""

import time

import numpy as np
import pytest

from bibfs_tpu.serve import (
    CircuitBreaker,
    ExecutableCache,
    FaultPlan,
    QueryEngine,
    QueryError,
    RetryPolicy,
)
from bibfs_tpu.serve.resilience import (
    HealthMonitor,
    classify_exception,
    healthz_status,
    to_query_error,
)
from bibfs_tpu.solvers.serial import solve_serial


def _skiplink_graph(n: int) -> np.ndarray:
    edges = [[i, i + 1] for i in range(n - 1)]
    edges += [[i, i + 7] for i in range(n - 7)]
    return np.array(edges)


def _check_oracle(n, edges, pairs, results):
    for (src, dst), r in zip(pairs, results):
        ref = solve_serial(n, edges, int(src), int(dst))
        assert r.found == ref.found, (src, dst)
        if ref.found:
            assert r.hops == ref.hops, (src, dst)


def _fresh(k, lo, span=50):
    return [(lo + i, lo + i + span) for i in range(k)]


# ---- taxonomy --------------------------------------------------------
def test_query_error_taxonomy_and_classification():
    e = QueryError("boom", kind="capacity", query=(3, 9))
    assert e.kind == "capacity" and e.query == (3, 9)
    assert "capacity" in str(e) and "3->9" in str(e)
    with pytest.raises(ValueError):
        QueryError("x", kind="mystery")
    assert classify_exception(TimeoutError()) == "timeout"
    assert classify_exception(RuntimeError("x")) == "internal"
    # a ValueError out of a SOLVER rung is an internal failure — only
    # submit-time validation may tag invalid, and it does so explicitly
    assert classify_exception(ValueError("x")) == "internal"
    w = to_query_error(ValueError("bad id"), (1, 2), kind="invalid")
    assert isinstance(w, QueryError) and w.kind == "invalid"
    assert to_query_error(ValueError("x")).kind == "internal"
    assert to_query_error(w) is w  # already structured: no re-wrap


# ---- retry policy ----------------------------------------------------
def test_retry_policy_backoff_and_jitter_bounds():
    p = RetryPolicy(attempts=4, base_ms=2.0, max_ms=10.0, jitter=0.5)
    for attempt, nominal in enumerate([2.0, 4.0, 8.0, 10.0]):
        for _ in range(20):
            d_ms = p.delay_s(attempt) * 1e3
            assert 0.5 * nominal <= d_ms <= 1.5 * nominal
    with pytest.raises(ValueError):
        RetryPolicy(attempts=0)
    # seeded: two same-seed policies give identical schedules
    a = RetryPolicy(seed=3)
    b = RetryPolicy(seed=3)
    assert [a.delay_s(0) for _ in range(5)] == [
        b.delay_s(0) for _ in range(5)
    ]


# ---- circuit breaker -------------------------------------------------
def test_breaker_full_lifecycle():
    t = [0.0]
    transitions = []
    br = CircuitBreaker(
        fail_threshold=2, reset_s=10.0, clock=lambda: t[0],
        on_transition=transitions.append,
    )
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"  # below threshold
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()  # open, window not elapsed
    t[0] = 10.5
    assert br.state == "half_open"  # window elapsed reads half-open
    assert br.allow()       # the single probe
    assert not br.allow()   # second caller blocked while probe in flight
    br.record_failure()     # probe failed: back to open, timer re-armed
    assert br.state == "open" and not br.allow()
    t[0] = 21.0
    assert br.allow()
    br.record_success()     # probe succeeded: closed, counters reset
    assert br.state == "closed" and br.allow()
    assert transitions == [
        "open", "half_open", "open", "half_open", "closed"
    ]
    assert br.snapshot()["opens"] == 2


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(fail_threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # never 3 CONSECUTIVE


# ---- health monitor --------------------------------------------------
def test_health_state_machine():
    t = [0.0]
    br = CircuitBreaker(fail_threshold=1, reset_s=100.0,
                        clock=lambda: t[0])
    depth = [0]
    h = HealthMonitor(
        breaker=br, window_s=5.0, queue_depth=lambda: depth[0],
        max_queue=10, clock=lambda: t[0],
    )
    assert h.state()[0] == "live"  # constructed, not ready yet
    h.set_ready()
    assert h.state()[0] == "ready"
    # breaker opens -> degraded with the reason named
    br.record_failure()
    state, reasons = h.state()
    assert state == "degraded" and any("breaker" in r for r in reasons)
    br.record_success()
    # recent errors degrade, then AGE OUT (recovery without a restart)
    h.note_error()
    assert h.state()[0] == "degraded"
    t[0] += 6.0
    assert h.state()[0] == "ready"
    # queue saturation degrades
    depth[0] = 9
    state, reasons = h.state()
    assert state == "degraded" and any("queue" in r for r in reasons)
    depth[0] = 0
    assert h.state()[0] == "ready"
    # draining is terminal and 503
    h.set_draining()
    assert h.state()[0] == "draining"
    assert healthz_status("ready") == 200
    assert healthz_status("degraded") == 200
    assert healthz_status("live") == 503
    assert healthz_status("draining") == 503


# ---- engine integration: the fallback ladder -------------------------
def test_device_fault_falls_back_to_host_oracle_correct():
    n = 220
    edges = _skiplink_graph(n)
    plan = FaultPlan.parse("device:every=1")
    eng = QueryEngine(n, edges, flush_threshold=8, device_batches=True,
                      faults=plan, exec_cache=ExecutableCache())
    pairs = _fresh(12, 0)
    results = eng.query_many(pairs)
    _check_oracle(n, edges, pairs, results)
    st = eng.stats()["resilience"]
    assert st["fallbacks"]["device->host"] == 1
    assert st["retries"] >= 1  # the route was retried before degrading
    assert st["errors"] == {k: 0 for k in st["errors"]}  # no ticket died


def test_transient_device_fault_retries_in_place():
    """times=1: the first dispatch fails, the RETRY succeeds — no
    fallback, no ticket failure, breaker stays closed."""
    n = 220
    edges = _skiplink_graph(n)
    plan = FaultPlan.parse("device:times=1")
    eng = QueryEngine(n, edges, flush_threshold=8, device_batches=True,
                      faults=plan, exec_cache=ExecutableCache())
    pairs = _fresh(10, 0)
    results = eng.query_many(pairs)
    _check_oracle(n, edges, pairs, results)
    st = eng.stats()["resilience"]
    assert st["retries"] == 1
    assert st["fallbacks"]["device->host"] == 0
    assert st["breaker"]["state"] == "closed"
    assert eng.counters["device_batches"] == 1


def test_breaker_opens_and_gates_device_then_recovers():
    n = 220
    edges = _skiplink_graph(n)
    plan = FaultPlan.parse("device:every=1")
    eng = QueryEngine(n, edges, flush_threshold=8, device_batches=True,
                      faults=plan, exec_cache=ExecutableCache())
    eng.query_many(_fresh(10, 0))    # 2 consecutive failures
    eng.query_many(_fresh(10, 60))   # 3rd -> breaker opens
    st = eng.stats()
    assert st["resilience"]["breaker"]["state"] == "open"
    assert st["health"]["state"] == "degraded"
    # open breaker short-circuits the device route: the fault seam is
    # never even reached
    fired = plan.stats()["fired_total"]
    eng.query_many(_fresh(10, 100))
    assert plan.stats()["fired_total"] == fired
    # fault clears; after reset_s a half-open probe closes the breaker
    plan.set_active(False)
    eng._breaker.reset_s = 0.01
    time.sleep(0.05)
    results = eng.query_many(_fresh(10, 120))
    _check_oracle(n, edges, _fresh(10, 120), results)
    st = eng.stats()
    assert st["resilience"]["breaker"]["state"] == "closed"
    assert st["health"]["state"] == "ready"
    assert st["resilience"]["breaker"]["opens"] == 1


def test_host_batch_fault_bisects_to_serial_rung():
    """The native-batch seam dies wholesale -> bisection drills down
    and every query still resolves through the serial rung (ladder:
    host-native -> serial), oracle-correct."""
    n = 150
    edges = _skiplink_graph(n)
    plan = FaultPlan.parse("host_batch:every=1")
    eng = QueryEngine(n, edges, flush_threshold=1000, faults=plan)
    pairs = _fresh(8, 0)
    results = eng.query_many(pairs)
    _check_oracle(n, edges, pairs, results)
    st = eng.stats()["resilience"]
    assert st["bisections"] >= 1
    assert st["fallbacks"]["host->serial"] == 8  # every singleton
    assert st["errors"]["internal"] == 0


def test_poison_query_fails_alone_with_structured_error():
    """One poisoned query (its batch raises whenever it is present AND
    its serial rung is broken) must fail exactly ITS ticket with a
    QueryError — its 7 batch peers resolve oracle-correct."""
    n = 150
    edges = _skiplink_graph(n)
    poison = (3, 53)
    plan = FaultPlan.parse(f"host_batch:pair={poison[0]}-{poison[1]}")
    eng = QueryEngine(n, edges, flush_threshold=1000, faults=plan)
    # break the last rung for the poison query only
    real_serial = eng._solve_serial_one

    def broken_serial(src, dst):
        if (src, dst) == poison:
            raise RuntimeError("serial rung poisoned too")
        return real_serial(src, dst)

    eng._solve_serial_one = broken_serial
    pairs = _fresh(8, 0)
    assert poison in pairs
    out = eng.query_many(pairs, return_errors=True)
    for (s, d), r in zip(pairs, out):
        if (s, d) == poison:
            assert isinstance(r, QueryError)
            assert r.kind == "internal" and r.query == poison
        else:
            ref = solve_serial(n, edges, s, d)
            assert r.found == ref.found and r.hops == ref.hops
    st = eng.stats()["resilience"]
    assert st["errors"]["internal"] == 1
    assert st["bisections"] >= 1
    assert eng.stats()["health"]["state"] == "degraded"
    # default mode raises that same structured error
    with pytest.raises(QueryError, match="internal"):
        eng.query_many([poison])


def test_query_many_return_errors_invalid_inputs():
    n = 50
    eng = QueryEngine(n, np.array([[0, 1], [1, 2]]))
    out = eng.query_many(
        [(0, 2), (0, 10 ** 9), (1, 2)], return_errors=True
    )
    assert out[0].found and out[2].found
    assert isinstance(out[1], QueryError) and out[1].kind == "invalid"
    assert eng.stats()["resilience"]["errors"]["invalid"] == 1
    # default mode still raises (pre-resilience contract)
    with pytest.raises(ValueError):
        eng.query_many([(0, 10 ** 9)])


def test_solve_many_return_errors_passthrough():
    from bibfs_tpu.solvers.api import solve_many

    n = 80
    edges = _skiplink_graph(n)
    out = solve_many(
        n, edges, [(0, 40), (0, 999)], return_errors=True
    )
    assert out[0].found
    assert isinstance(out[1], QueryError) and out[1].kind == "invalid"


def test_latency_fault_slows_but_never_fails():
    n = 150
    edges = _skiplink_graph(n)
    plan = FaultPlan.parse("host_batch:every=1,kind=latency,ms=20")
    eng = QueryEngine(n, edges, flush_threshold=1000, faults=plan)
    t0 = time.perf_counter()
    results = eng.query_many(_fresh(5, 0))
    assert time.perf_counter() - t0 >= 0.015
    _check_oracle(n, edges, _fresh(5, 0), results)
    st = eng.stats()["resilience"]
    assert st["fallbacks"]["host->serial"] == 0
    assert st["errors"]["internal"] == 0


def test_sync_close_marks_draining():
    eng = QueryEngine(20, np.array([[0, 1]]))
    assert eng.health_snapshot()["state"] == "ready"
    eng.close()
    assert eng.health_snapshot()["state"] == "draining"


def test_faults_from_env_reach_engine(monkeypatch):
    from bibfs_tpu.serve.faults import ENV_VAR

    n = 150
    edges = _skiplink_graph(n)
    monkeypatch.setenv(ENV_VAR, "host_batch:every=1")
    eng = QueryEngine(n, edges, flush_threshold=1000)
    results = eng.query_many(_fresh(6, 0))
    _check_oracle(n, edges, _fresh(6, 0), results)
    # the env-built plan really fired through the engine seam
    assert eng.stats()["resilience"]["faults"]["fired_total"] >= 1
    assert eng.stats()["resilience"]["fallbacks"]["host->serial"] == 6


def test_client_errors_do_not_degrade_health():
    """invalid submits (and caller cancels) are the CLIENT's failures:
    they count in bibfs_errors_total but must not flip /healthz —
    otherwise whoever talks to the socket controls the health alerts."""
    n = 50
    eng = QueryEngine(n, np.array([[0, 1], [1, 2]]))
    for _ in range(5):
        out = eng.query_many([(0, 10 ** 9)], return_errors=True)
        assert isinstance(out[0], QueryError)
    st = eng.stats()
    assert st["resilience"]["errors"]["invalid"] == 5
    assert st["health"]["state"] == "ready"
    assert st["health"]["recent_errors"] == 0


def test_shared_breaker_updates_every_engines_gauge():
    """One breaker shared by two engines (one accelerator, several
    engines): a transition must land on BOTH engines' breaker gauges,
    not just whichever engine was constructed first."""
    from bibfs_tpu.obs.metrics import REGISTRY

    edges = np.array([[0, 1], [1, 2]])
    shared = CircuitBreaker(fail_threshold=1)
    a = QueryEngine(30, edges, breaker=shared)
    b = QueryEngine(30, edges, breaker=shared)
    gauges = [
        REGISTRY.gauge("bibfs_breaker_state", "", ("engine",))
        .labels(engine=e.obs_label) for e in (a, b)
    ]
    assert [g.value for g in gauges] == [0, 0]
    shared.record_failure()  # -> open
    assert [g.value for g in gauges] == [2, 2]
    assert a.health_snapshot()["state"] == "degraded"
    assert b.health_snapshot()["state"] == "degraded"


def test_breaker_metrics_track_state():
    from bibfs_tpu.obs.metrics import REGISTRY

    n = 220
    edges = _skiplink_graph(n)
    plan = FaultPlan.parse("device:every=1")
    eng = QueryEngine(
        n, edges, flush_threshold=8, device_batches=True,
        faults=plan, exec_cache=ExecutableCache(),
        breaker=CircuitBreaker(fail_threshold=2),
    )
    gauge = REGISTRY.gauge(
        "bibfs_breaker_state", "", ("engine",)
    ).labels(engine=eng.obs_label)
    assert gauge.value == 0
    eng.query_many(_fresh(10, 0))  # 2 failures -> open
    assert gauge.value == 2
    trans = REGISTRY.counter(
        "bibfs_breaker_transitions_total", "", ("engine", "to"),
    ).labels(engine=eng.obs_label, to="open")
    assert trans.value == 1
