"""Fleet serving: the health-aware router's policy logic (stub
replicas — routing affinity, health-driven table, failure re-routing,
spill, rolling-swap choreography) and the real-replica integrations
(in-process engines over per-replica stores; spawned ``bibfs-serve``
subprocesses)."""

import time

import numpy as np
import pytest

from bibfs_tpu.fleet import (
    ReplicaDead,
    Router,
    engine_replica,
)
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.serve.resilience import QueryError, RetryPolicy
from bibfs_tpu.solvers.api import BFSResult
from bibfs_tpu.solvers.serial import solve_serial
from bibfs_tpu.store import GraphStore


def _skiplink_graph(n: int) -> np.ndarray:
    edges = [[i, i + 1] for i in range(n - 1)]
    edges += [[i, i + 7] for i in range(n - 7)]
    return np.array(edges)


N = 80
EDGES = _skiplink_graph(N)


class _StubTicket:
    def __init__(self, src, dst, result=None, error=None):
        self.src, self.dst = src, dst
        self.result = result
        self.error = error


class StubReplica:
    """Replica-shaped test double: resolves every query inline with a
    recognizable result (hops = src + dst), scriptable health/load and
    failure modes."""

    kind = "stub"

    def __init__(self, name):
        self.name = name
        self.state = "ready"
        self._load = 0
        self.fail_submits = False
        self.fail_tickets = False
        self.dead = False
        self.submitted = []
        self.events = []
        self._version = 1

    def submit(self, src, dst, graph=None):
        if self.dead:
            raise ReplicaDead(self.name)
        if self.fail_submits:
            raise QueryError("stub refusing", kind="capacity",
                             query=(src, dst))
        self.submitted.append((graph, src, dst))
        if self.fail_tickets:
            return _StubTicket(src, dst, error=QueryError(
                "stub ticket failure", kind="internal",
                query=(src, dst),
            ))
        return _StubTicket(
            src, dst,
            result=BFSResult(True, src + dst, None, None, 0.0, 0, 0),
        )

    def wait_ticket(self, t, timeout=None):
        if t.error is not None:
            raise t.error
        return t.result

    def flush(self, timeout=None):
        self.events.append("flush")

    def load(self):
        return self._load

    def health(self):
        if self.dead:
            raise ReplicaDead(self.name)
        return {"state": self.state}

    def stats(self):
        return {}

    def version(self, graph=None):
        return self._version

    def begin_drain(self):
        self.events.append("begin_drain")
        return True

    def end_drain(self):
        self.events.append("end_drain")
        return True

    def roll(self, graph=None, adds=(), dels=()):
        self.events.append(("roll", graph, len(adds), len(dels)))
        if adds or dels:
            self._version += 1
        return self._version

    def probe(self, graph=None, timeout=5.0):
        self.events.append("probe")
        return True

    def kill(self):
        self.dead = True

    def restart(self):
        self.dead = False

    def close(self):
        self.events.append("close")


def _stub_router(k=3, **kw):
    stubs = [StubReplica(f"s{i}") for i in range(k)]
    kw.setdefault("poll_interval_s", 0.05)
    return Router(stubs, **kw), stubs


def test_hash_affinity_is_stable():
    router, stubs = _stub_router()
    try:
        owners = {g: router.owner(g) for g in ("a", "b", "c", "d")}
        for g, owner in owners.items():
            for _ in range(5):
                t = router.submit(1, 2, g)
                assert t.replica == owner  # idle fleet: pure affinity
    finally:
        router.close(close_replicas=False)


def test_degraded_demoted_dead_ejected_readmitted():
    router, stubs = _stub_router(2)
    try:
        owner = router.owner("g")
        other = next(s for s in stubs if s.name != owner)
        owner_stub = next(s for s in stubs if s.name == owner)
        # degraded owner: traffic prefers the ready peer
        owner_stub.state = "degraded"
        router._poll_once()
        assert router.submit(1, 2, "g").replica == other.name
        # dead owner: ejected (health raises)
        owner_stub.state = "ready"
        owner_stub.dead = True
        router._poll_once()
        assert router.table()[owner] == "dead"
        assert router.submit(1, 2, "g").replica == other.name
        # recovery: re-admitted, affinity restored
        owner_stub.dead = False
        router._poll_once()
        assert router.table()[owner] == "ready"
        assert router.submit(1, 2, "g").replica == owner
    finally:
        router.close(close_replicas=False)


def test_submit_failure_reroutes_and_counts():
    router, stubs = _stub_router(3)
    try:
        owner = router.owner("g")
        owner_stub = next(s for s in stubs if s.name == owner)
        owner_stub.fail_submits = True
        before = router.stats()["reroutes"]
        t = router.submit(3, 4, "g")
        assert t.replica != owner
        assert t.wait(timeout=5.0).hops == 7
        assert router.stats()["reroutes"] > before
    finally:
        router.close(close_replicas=False)


def test_ticket_failure_reroutes_on_wait():
    router, stubs = _stub_router(2, retry=RetryPolicy(attempts=3))
    try:
        owner = router.owner("g")
        owner_stub = next(s for s in stubs if s.name == owner)
        owner_stub.fail_tickets = True
        t = router.submit(3, 4, "g")
        assert t.replica == owner  # submit itself succeeded
        res = t.wait(timeout=10.0)
        assert res.hops == 7 and t.replica != owner
        assert t.attempts == 2
    finally:
        router.close(close_replicas=False)


def test_invalid_never_reroutes():
    router, stubs = _stub_router(2)
    try:
        owner = router.owner("g")
        owner_stub = next(s for s in stubs if s.name == owner)

        orig = owner_stub.submit

        def bad_submit(src, dst, graph=None):
            raise ValueError("src/dst out of range")

        owner_stub.submit = bad_submit
        with pytest.raises(ValueError):
            router.submit(999, 999, "g")
        owner_stub.submit = orig
    finally:
        router.close(close_replicas=False)


def test_all_dead_raises_capacity():
    router, stubs = _stub_router(2)
    try:
        for s in stubs:
            s.dead = True
        router._poll_once()
        with pytest.raises(QueryError) as exc:
            router.submit(1, 2, "g")
        assert exc.value.kind == "capacity"
    finally:
        router.close(close_replicas=False)


def test_spill_to_least_loaded():
    router, stubs = _stub_router(3, spill_after=4)
    try:
        owner = router.owner("hot")
        for s in stubs:
            s._load = 0 if s.name != owner else 100
        before = router.stats()["spills"]
        t = router.submit(1, 2, "hot")
        assert t.replica != owner
        assert router.stats()["spills"] == before + 1
    finally:
        router.close(close_replicas=False)


def test_rolling_swap_choreography_and_metrics():
    router, stubs = _stub_router(2)
    try:
        out = router.rolling_swap("g", adds=[(0, 1)], dels=[])
        assert out["ok"], out
        assert router.stats()["rolls"] == 1
        for s in stubs:
            # drain -> flush -> roll -> end_drain -> probe, in order
            assert s.events[0] == "begin_drain"
            assert "flush" in s.events
            roll_i = s.events.index(("roll", "g", 1, 0))
            assert s.events.index("end_drain") > roll_i
            assert "probe" in s.events
            assert s._version == 2
        for row in out["replicas"]:
            assert row["version"] == [1, 2]
        # the fleet families render
        text = REGISTRY.render()
        for fam in ("bibfs_fleet_replicas", "bibfs_fleet_routed_total",
                    "bibfs_fleet_reroutes_total",
                    "bibfs_fleet_rolls_total",
                    "bibfs_fleet_spills_total"):
            assert fam in text, fam
    finally:
        router.close(close_replicas=False)


# ---- real in-process replicas ---------------------------------------

def _make_engine_replica(idx, graphs=("a",), **kw):
    store = GraphStore(compact_threshold=None)
    for g in graphs:
        store.add(g, N, EDGES)
    kw.setdefault("cache_entries", 8)
    kw.setdefault("max_batch", 16)
    return engine_replica(f"r{idx}", store, **kw)


def test_engine_fleet_serves_correctly():
    router = Router(
        [_make_engine_replica(i, ("a", "b")) for i in range(3)],
        poll_interval_s=0.1,
    )
    try:
        pairs = [(0, 50), (3, 40), (11, 70), (2, 2)]
        for g in ("a", "b"):
            results = router.query_many(pairs, graph=g)
            for (s, d), res in zip(pairs, results):
                ref = solve_serial(N, EDGES, s, d)
                assert res.found == ref.found
                assert res.hops == ref.hops, (g, s, d)
    finally:
        router.close()


def test_engine_fleet_kill_restart_reroute():
    router = Router(
        [_make_engine_replica(i) for i in range(3)],
        poll_interval_s=0.1,
    )
    try:
        owner = router.owner("a")
        # park a ticket on the owner, then crash it: the failure must
        # re-route on wait and the answer stay exact
        t = router.submit(0, 50, "a")
        assert t.replica == owner
        router.replica(owner).kill()
        ref = solve_serial(N, EDGES, 0, 50)
        assert t.wait(timeout=30.0).hops == ref.hops
        assert t.replica != owner
        assert router.stats()["reroutes"] > 0
        # dead in the table; new traffic avoids it
        deadline = time.monotonic() + 5.0
        while (router.table()[owner] != "dead"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert router.table()[owner] == "dead"
        assert router.submit(3, 40, "a").replica != owner
        # restart over the same store; the poller re-admits
        router.replica(owner).restart()
        deadline = time.monotonic() + 5.0
        while (router.table()[owner] != "ready"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert router.table()[owner] == "ready"
        assert router.query(5, 60, "a").hops == solve_serial(
            N, EDGES, 5, 60
        ).hops
    finally:
        router.close()


def test_engine_fleet_rolling_swap_mixed_versions():
    """Mid-roll the fleet serves mixed versions, each replica exact for
    the version it declares; post-roll every replica declares v2 and
    answers on the updated graph."""
    router = Router(
        [_make_engine_replica(i) for i in range(2)],
        poll_interval_s=0.1,
    )
    try:
        ref_v1 = solve_serial(N, EDGES, 0, N - 1)
        t0 = router.submit(0, N - 1, "a")
        assert t0.wait(timeout=30.0).hops == ref_v1.hops
        assert t0.declared_version == 1

        out = router.rolling_swap("a", adds=[(0, N - 1)], dels=[])
        assert out["ok"], out
        for name in router.replica_names:
            assert router.replica(name).version("a") == 2
        t1 = router.submit(0, N - 1, "a")
        assert t1.wait(timeout=30.0).hops == 1  # the added shortcut
        assert t1.declared_version == 2
    finally:
        router.close()


def test_engine_fleet_drain_reroutes_live_traffic():
    """While one replica drains (rolling-swap window), its submits are
    refused with structured capacity errors and the router routes
    around it — no caller ever sees the refusal."""
    router = Router(
        [_make_engine_replica(i) for i in range(2)],
        poll_interval_s=0.05,
    )
    try:
        owner = router.owner("a")
        router.replica(owner).begin_drain()
        for _ in range(4):
            t = router.submit(0, 50, "a")
            assert t.replica != owner
            assert t.wait(timeout=30.0) is not None
        router.replica(owner).end_drain()
    finally:
        router.close()


@pytest.mark.slow
def test_run_fleet_harness_end_to_end():
    """A miniature fleet soak through the public harness: qps phases
    (ratio reported, not gated at this scale), kill/restart with
    recovery, a rolling swap under load, spill burst, live /metrics —
    zero lost, all verified. (The CI fleet smoke runs the bench.py
    wrapper of this same harness; marked slow to keep it out of the
    tier-1 budget.)"""
    from bibfs_tpu.serve.loadgen import run_fleet

    out = run_fleet(
        replicas=3, graphs=6, grid=(24, 24), queries=300,
        chaos_queries=240, chaos_span_s=6.0, hot_pool=12,
        cache_entries=16, qps_factor=None, recovery_bound_s=30.0,
        burst_queries=90,
    )
    assert out["zero_lost"], out["tickets"]
    assert out["zero_failed"], out["failed_sample"]
    assert out["verified_vs_truth"], out["mismatches"]
    assert out["recovery_ok"], out["chaos"]
    assert out["roll_ok"], out["roll"]
    assert out["reroutes_ok"] and out["spill_ok"]
    assert out["metrics_ok"], out["metrics"]
    assert out["ok"]


# ---- subprocess replicas --------------------------------------------

@pytest.mark.slow
def test_process_replica_fleet(tmp_path):
    """Real ``bibfs-serve`` subprocess replicas behind the router:
    routing, the health/stats control surface, a REAL process kill
    (in-flight queries die with the interpreter and re-route), restart
    and re-admission."""
    from bibfs_tpu.fleet import ProcessReplica
    from bibfs_tpu.graph.io import write_graph_bin

    gpath = tmp_path / "g.bin"
    write_graph_bin(gpath, N, EDGES)
    router = Router(
        [ProcessReplica(f"p{i}", str(gpath)) for i in range(2)],
        poll_interval_s=0.2,
    )
    try:
        results = router.query_many([(0, 50), (3, 40), (0, N - 1)])
        for (s, d), res in zip([(0, 50), (3, 40), (0, N - 1)], results):
            assert res.hops == solve_serial(N, EDGES, s, d).hops
        st = router.replica(router.owner(None)).stats()
        assert st["queries"] >= 1
        t = router.submit(5, 60)
        victim = t.replica
        router.replica(victim).kill()  # SIGKILL: real crash chaos
        assert t.wait(timeout=60.0).hops == solve_serial(
            N, EDGES, 5, 60
        ).hops
        assert t.replica != victim
        router.replica(victim).restart()
        deadline = time.monotonic() + 30.0
        while (router.table()[victim] != "ready"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert router.table()[victim] == "ready"
    finally:
        router.close()


@pytest.mark.slow
def test_process_replica_store_rolling_swap(tmp_path):
    """A rolling swap across ``--store`` subprocess replicas: the
    update batch lands through each child's stdin control surface
    (``use``/``update``/``swap``), versions advance, and post-roll
    answers reflect the new edge set."""
    from bibfs_tpu.fleet import ProcessReplica
    from bibfs_tpu.graph.io import write_graph_bin

    store_dir = tmp_path / "store"
    store_dir.mkdir()
    write_graph_bin(store_dir / "a.bin", N, EDGES)
    router = Router(
        [ProcessReplica(f"p{i}", store_dir=str(store_dir))
         for i in range(2)],
        poll_interval_s=0.2,
    )
    try:
        ref = solve_serial(N, EDGES, 0, N - 1)
        assert router.query(0, N - 1, "a").hops == ref.hops
        out = router.rolling_swap("a", adds=[(0, N - 1)], dels=[])
        assert out["ok"], out
        for row in out["replicas"]:
            assert row["version"] == [1, 2]
        assert router.query(0, N - 1, "a").hops == 1
        # a refused `use` (unknown graph) must FAIL the query, never
        # silently answer it against the child's previous graph
        rep = router.replica("p0")
        bad = rep.submit(0, 5, "nope")
        with pytest.raises(QueryError) as exc:
            rep.wait_ticket(bad, timeout=30.0)
        assert exc.value.kind == "invalid"
        # and the replica recovers: the next good-graph query re-`use`s
        # (expected hops on the POST-roll graph, shortcut included)
        edges_v2 = np.vstack([EDGES, [[0, N - 1]]])
        assert rep.wait_ticket(
            rep.submit(0, 50, "a"), timeout=30.0
        ).hops == solve_serial(N, edges_v2, 0, 50).hops
    finally:
        router.close()
