"""Cold-tier varint+delta CSR codec (bibfs_tpu/graph/compress):
bit-exact round-trips on every graph family the store serves, vectorized
decode, and loud rejection of foreign byte streams — a cold snapshot
that decodes to ANYTHING but its exact adjacency would silently serve
wrong answers after a promote."""

import numpy as np
import pytest

from bibfs_tpu.graph.compress import (
    CompressedCSR,
    decode_csr,
    encode_csr,
    encode_snapshot_csr,
)
from bibfs_tpu.graph.csr import build_csr
from bibfs_tpu.graph.generate import grid_graph, rmat_graph


def _roundtrip(n, edges):
    row_ptr, col_ind = build_csr(n, edges)
    c = encode_csr(row_ptr, col_ind)
    d_rp, d_ci = decode_csr(c)
    assert np.array_equal(d_rp, row_ptr)
    assert np.array_equal(d_ci, col_ind)
    assert d_ci.dtype == col_ind.dtype
    return c


def test_roundtrip_random_graphs():
    rng = np.random.default_rng(0)
    for _ in range(10):
        n = int(rng.integers(2, 400))
        m = int(rng.integers(0, 4 * n))
        _roundtrip(n, rng.integers(0, n, size=(m, 2)))


def test_roundtrip_grid():
    w, h = 23, 17
    _roundtrip(w * h, grid_graph(w, h, perforation=0.05, seed=1))


def test_roundtrip_rmat():
    n, edges = rmat_graph(10, 8, seed=2)
    c = _roundtrip(n, edges)
    # power-law adjacency with sorted within-row neighbors delta-codes
    # well below raw int32 — the cold tier's whole point
    assert c.ratio > 1.5


def test_roundtrip_empty_and_isolated_tail():
    # trailing empty rows exercise the first-neighbor-absolute seam
    _roundtrip(5, np.zeros((0, 2), dtype=np.int64))
    _roundtrip(9, np.array([[0, 1], [1, 2]]))


def test_large_ids_roundtrip():
    # ids past 2**28 need 5 varint groups — the full group ladder
    # (hand-built CSR: a 2**31-node row_ptr would be 17 GB)
    big = (1 << 31) - 1
    row_ptr = np.array([0, 2, 4], dtype=np.int64)
    col_ind = np.array([1, big, 5, big - 7], dtype=np.int64)
    c = encode_csr(row_ptr, col_ind)
    d_rp, d_ci = decode_csr(c)
    assert np.array_equal(d_rp, row_ptr)
    assert np.array_equal(d_ci, col_ind)


def test_stats_accounting():
    n, edges = rmat_graph(8, 6, seed=3)
    c = _roundtrip(n, edges)
    s = c.stats()
    assert s["compressed_bytes"] == c.data.size + c.row_ptr.nbytes
    assert s["raw_bytes"] == c.raw_bytes
    assert s["nnz"] == c.nnz


def test_encode_rejects_unsorted_rows():
    # within-row deltas require the canonical sorted-neighbor CSR;
    # encoding an unsorted one would write negative deltas as garbage
    row_ptr = np.array([0, 2], dtype=np.int64)
    col_ind = np.array([5, 1], dtype=np.int64)
    with pytest.raises(ValueError, match="sorted"):
        encode_csr(row_ptr, col_ind)


def test_decode_rejects_foreign_stream():
    n, edges = rmat_graph(6, 4, seed=4)
    row_ptr, col_ind = build_csr(n, edges)
    c = encode_csr(row_ptr, col_ind)
    # truncated payload: fewer varints than nnz
    torn = CompressedCSR(
        n=c.n, nnz=c.nnz, row_ptr=c.row_ptr, data=c.data[:-2]
    )
    with pytest.raises(ValueError):
        decode_csr(torn)
    # garbage: all-continuation bytes never terminate a varint group
    junk = CompressedCSR(
        n=c.n, nnz=c.nnz, row_ptr=c.row_ptr,
        data=np.full(c.data.size, 0x80, dtype=np.uint8),
    )
    with pytest.raises(ValueError):
        decode_csr(junk)


def test_encode_snapshot_csr():
    from bibfs_tpu.store import GraphSnapshot

    n, edges = rmat_graph(8, 4, seed=5)
    snap = GraphSnapshot.build(n, edges)
    c = encode_snapshot_csr(snap)
    d_rp, d_ci = decode_csr(c)
    s_rp, s_ci = snap.csr()
    assert np.array_equal(d_rp, s_rp)
    assert np.array_equal(d_ci, s_ci)
