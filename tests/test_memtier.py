"""Memory-tier integration: the residency accountant's promote/demote
ledger, ``memory_stats()``/``memory`` command shape, the engine's
zero-copy native solver over a mapped snapshot, render-at-zero for the
three store memory metrics, and the satellite regression — TWO
``ProcessReplica``s serving one durable store directory, with
SIGKILL/respawn recovering by remap and the recovered digest verified."""

import numpy as np
import pytest

from bibfs_tpu.graph.io import write_graph_bin
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.solvers.serial import solve_serial
from bibfs_tpu.store import GraphStore, content_digest

N = 60
EDGES = np.array([[i, i + 1] for i in range(N - 1)]
                 + [[i, i + 7] for i in range(N - 7)])


def _seed_dir(tmp_path):
    d = tmp_path / "store"
    d.mkdir(exist_ok=True)
    write_graph_bin(d / "g.bin", N, EDGES)
    return str(d)


# ---- metrics --------------------------------------------------------
def test_memtier_metrics_render_at_zero():
    """All three memory-tier families render BEFORE any traffic — a
    dashboard pointed at a fresh store sees zeros, not absent series."""
    st = GraphStore(compact_threshold=None, obs_label="t-mem0")
    r = REGISTRY.render()
    for name in ("bibfs_store_mmap_bytes", "bibfs_store_tier",
                 "bibfs_store_remap_total"):
        assert name in r
    for tier in ("mapped", "hot", "cold"):
        assert f'bibfs_store_tier{{store="t-mem0",tier="{tier}"}} 0' in r
    st.add("g", 10, np.array([[0, 1], [1, 2]]))
    r = REGISTRY.render()
    # per-graph series mint at zero on add (no sidecar, no remap yet)
    assert 'bibfs_store_mmap_bytes{store="t-mem0",graph="g"} 0' in r
    assert 'bibfs_store_remap_total{store="t-mem0",graph="g"} 0' in r
    assert 'bibfs_store_tier{store="t-mem0",tier="hot"} 1' in r
    st.close()


def test_memtier_metrics_track_remap(tmp_path):
    d = _seed_dir(tmp_path)
    GraphStore.from_dir(d, durable=True, compact_threshold=None,
                        obs_label="t-mem1").close()
    st = GraphStore.from_dir(d, durable=True, compact_threshold=None,
                             obs_label="t-mem1")
    r = REGISTRY.render()
    assert 'bibfs_store_remap_total{store="t-mem1",graph="g"} 1' in r
    assert 'bibfs_store_tier{store="t-mem1",tier="mapped"} 1' in r
    mapped = st.memory_stats()["graphs"]["g"]["mapped_bytes"]
    assert (f'bibfs_store_mmap_bytes{{store="t-mem1",graph="g"}} '
            f'{mapped}') in r
    st.close()


# ---- accountant -----------------------------------------------------
def test_memory_stats_shape():
    st = GraphStore(compact_threshold=None)
    st.add("g", 10, np.array([[0, 1], [1, 2]]))
    ms = st.memory_stats()
    for key in ("graphs", "resident_bytes", "mapped_bytes",
                "residency_budget", "headroom_bytes", "mmap_arrays"):
        assert key in ms
    g = ms["graphs"]["g"]
    for key in ("tier", "resident_bytes", "mapped_bytes", "cold_bytes",
                "promotions", "demotions", "version", "digest",
                "arrays"):
        assert key in g
    assert g["tier"] == "hot" and g["resident_bytes"] > 0
    assert ms["residency_budget"] is None
    st.close()


def test_residency_accountant_demotes_and_promotes_exactly():
    """Budget pressure demotes hot graphs to the compressed cold tier;
    ANY access promotes back bit-exactly (digest-verified) and the
    ledger counts both directions."""
    st = GraphStore(compact_threshold=None, residency_budget=1)
    rng = np.random.default_rng(11)
    st.add("g1", 80, rng.integers(0, 80, size=(200, 2)))
    st.add("g2", 80, rng.integers(0, 80, size=(200, 2)))
    ms = st.memory_stats()
    assert ms["headroom_bytes"] < 0
    for g in ("g1", "g2"):
        assert ms["graphs"][g]["tier"] == "cold"
        assert ms["graphs"][g]["demotions"] >= 1
        assert ms["graphs"][g]["cold_bytes"] > 0
    digest = ms["graphs"]["g1"]["digest"]
    snap = st.acquire("g1")
    try:
        # touching pairs promotes — and the promoted bytes are EXACT
        assert content_digest(snap.n, snap.pairs) == digest
        assert snap.tier == "hot"
        assert st.memory_stats()["graphs"]["g1"]["promotions"] >= 1
    finally:
        snap.release()
    st.rebalance()  # pressure still over budget: demoted again
    assert st.memory_stats()["graphs"]["g1"]["tier"] == "cold"
    # solves against the re-promoted graph still answer exactly
    res = st.current("g1")
    rp, ci = res.csr()
    assert rp[-1] == ci.size
    st.close()


def test_accountant_respects_budget_headroom():
    st = GraphStore(compact_threshold=None,
                    residency_budget=1 << 30)
    st.add("g", 10, np.array([[0, 1], [1, 2]]))
    ms = st.memory_stats()
    assert ms["graphs"]["g"]["tier"] == "hot"  # plenty of headroom
    assert ms["headroom_bytes"] > 0
    st.close()


def test_rejects_negative_budget():
    with pytest.raises(ValueError, match="residency_budget"):
        GraphStore(compact_threshold=None, residency_budget=-1)


# ---- engine zero-copy -----------------------------------------------
def test_runtime_host_solver_is_zero_copy_on_mapped(tmp_path):
    """The serving win: a runtime over a MAPPED snapshot hands the C
    solver the sidecar's csr32 table directly — the column array is the
    memmap itself, nothing was copied resident, and answers are exact."""
    from bibfs_tpu.serve.engine import _GraphRuntime

    d = _seed_dir(tmp_path)
    GraphStore.from_dir(d, durable=True, compact_threshold=None).close()
    st = GraphStore.from_dir(d, durable=True, compact_threshold=None)
    snap = st.acquire("g")
    try:
        assert snap.tier == "mapped"
        rt = _GraphRuntime(snap)
        solver = rt.get_host_solver()
        if rt.host_backend_resolved != "native":
            pytest.skip("native runtime unavailable")
        assert isinstance(rt.host_native_graph.col_ind, np.memmap)
        for s, t in ((0, N - 1), (3, 40), (7, 7)):
            assert solver(s, t).hops == solve_serial(N, EDGES, s, t).hops
        # serving never touched .pairs: the snapshot stayed on the map
        assert snap.resident_bytes() == 0
    finally:
        snap.release()
        st.close()


# ---- replicas -------------------------------------------------------
def test_inprocess_replica_memory_command(tmp_path):
    from bibfs_tpu.fleet import engine_replica

    st = GraphStore(compact_threshold=None)
    st.add("g", N, EDGES)
    rep = engine_replica("m0", st)
    try:
        ms = rep.memory()
        assert ms["graphs"]["g"]["tier"] == "hot"
    finally:
        rep.close()

    from bibfs_tpu.fleet.replica import EngineReplica
    from bibfs_tpu.serve.engine import QueryEngine

    st2 = GraphStore(compact_threshold=None)
    st2.add("g", N, EDGES)
    lone = EngineReplica("m1", lambda: QueryEngine(store=st2, graph="g"))
    try:
        with pytest.raises(ValueError, match="no store"):
            lone.memory()
    finally:
        lone.close()
        st2.close()


def test_two_process_replicas_share_one_durable_dir(tmp_path):
    """Satellite regression: TWO ProcessReplicas over ONE durable store
    dir both serve exact answers from the MAPPED tier (one page-cache
    copy, zero python-resident adjacency), and a SIGKILL/respawn
    recovers by remap with the recovered digest verified."""
    from bibfs_tpu.fleet.replica import ProcessReplica

    d = _seed_dir(tmp_path)
    st = GraphStore.from_dir(d, durable=True, compact_threshold=None)
    digest = st.current("g").digest
    st.close()

    reps = [ProcessReplica(f"m{i}", store_dir=d, durable=True,
                           fsync="off") for i in range(2)]
    try:
        for rep in reps:
            mem = rep.memory(timeout=30.0)
            g = mem["graphs"]["g"]
            assert g["tier"] == "mapped", g
            assert g["mapped_bytes"] > 0
            assert g["resident_bytes"] == 0  # bounded private copy
            assert g["digest"] == digest
            for s, t in ((0, N - 1), (5, 44)):
                got = rep.wait_ticket(rep.submit(s, t, "g"),
                                      timeout=60.0)
                assert got.hops == solve_serial(N, EDGES, s, t).hops
        # chaos: SIGKILL one replica, respawn — recovery must REMAP
        victim = reps[0]
        victim.kill()
        victim.restart()
        g = victim.memory(timeout=30.0)["graphs"]["g"]
        assert g["tier"] == "mapped" and g["digest"] == digest
        got = victim.wait_ticket(victim.submit(0, N - 1, "g"),
                                 timeout=60.0)
        assert got.hops == solve_serial(N, EDGES, 0, N - 1).hops
    finally:
        for rep in reps:
            rep.close()
